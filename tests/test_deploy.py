"""Deploy lifecycle: release registry, warm swap, canary, rollback.

Covers the deploy/ subsystem contracts end-to-end without training:
models are built from random factors (the test_query_batcher recipe) and
persisted through the real Models store, so the /deploy.json path runs
load -> warmup -> verify -> swap against real storage in milliseconds.

The two acceptance paths the ISSUE names are here:
  * a canary deploy with an injected latency (and, separately, error)
    regression auto-rolls back to the incumbent; a healthy canary
    auto-promotes — both visible in pio_deploy_* metrics;
  * a warm swap serves post-cutover traffic with ZERO new XLA compiles
    for bucketed shapes (compile-counter delta across the swap is 0),
    while a cold swap of a new catalog demonstrably compiles.
"""

import asyncio
import functools
import json
import threading
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

import predictionio_tpu.models.als as als_mod
from predictionio_tpu.core.base import Algorithm, Serving
from predictionio_tpu.core.engine import Engine, TrainResult
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.deploy.canary import (
    CanaryConfig, CanaryController, SlidingStats, TrafficSplitter,
)
from predictionio_tpu.deploy.releases import (
    model_digest, params_digest, record_release, resolve_release,
)
from predictionio_tpu.deploy.warm import (
    ServingUnit, warmup_ladder, warmup_unit,
)
from predictionio_tpu.engines.recommendation import (
    ALSAlgorithm, AlgorithmParams, RecommendationServing,
)
from predictionio_tpu.models.als import ALSModel
from predictionio_tpu.obs.jax_stats import compile_counter
from predictionio_tpu.obs.registry import default_registry
from predictionio_tpu.server.query_server import QueryServer
from predictionio_tpu.storage import Model, Release, Storage
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.utils.server_config import DeployConfig, ServingConfig
from predictionio_tpu.workflow.serialization import serialize_models

pytestmark = pytest.mark.anyio

N_USERS, RANK = 40, 6
ENGINE_ID, VARIANT = "deploy-test-engine", "default"


def make_als_model(seed=0, n_items=30) -> ALSModel:
    rng = np.random.default_rng(seed)
    return ALSModel(
        user_vocab=np.sort(np.asarray(
            [f"u{i}" for i in range(N_USERS)], dtype=object)),
        item_vocab=np.sort(np.asarray(
            [f"i{i}" for i in range(n_items)], dtype=object)),
        U=rng.normal(size=(N_USERS, RANK)).astype(np.float32),
        V=rng.normal(size=(n_items, RANK)).astype(np.float32))


def make_engine(algo_cls=ALSAlgorithm) -> Engine:
    """A recommendation-shaped engine whose deploy path instantiates
    `algo_cls` — candidate releases prepared through /deploy.json score
    with it (the regression-injection seam)."""
    import predictionio_tpu.engines.recommendation as rec

    return Engine(
        data_source_classes=rec.RecommendationDataSource,
        preparator_classes=rec.RecommendationPreparator,
        algorithm_classes={"als": algo_cls},
        serving_classes=RecommendationServing,
    )


def make_server(model=None, engine=None, instance=None, release=None,
                deploy_config=None, serving_config=None) -> QueryServer:
    model = model if model is not None else make_als_model()
    result = TrainResult(models=[model],
                         algorithms=[ALSAlgorithm(AlgorithmParams())],
                         serving=RecommendationServing(),
                         engine_params=EngineParams())
    instance = instance or EngineInstance(
        id="deploy-incumbent", engine_id=ENGINE_ID, engine_version="1",
        engine_variant=VARIANT, status="COMPLETED")
    return QueryServer(
        engine or make_engine(), result, instance, ctx=None,
        serving_config=serving_config or ServingConfig(
            batch_max=16, batch_linger_s=0.0, batch_inflight=2),
        deploy_config=deploy_config or DeployConfig(
            warmup=True, drain_timeout_s=10.0),
        release=release)


@pytest.fixture()
def deploy_store(tmp_path):
    Storage.configure({
        "sources": {"DB": {"TYPE": "sqlite",
                           "PATH": str(tmp_path / "deploy.db")}},
        "repositories": {
            "METADATA": {"NAME": "pio", "SOURCE": "DB"},
            "EVENTDATA": {"NAME": "pio", "SOURCE": "DB"},
            "MODELDATA": {"NAME": "pio", "SOURCE": "DB"},
        },
    })
    yield Storage
    Storage.reset()


def register_candidate(seed=1, n_items=30, instance_id="deploy-candidate"):
    """Persist a factors-only model as a COMPLETED instance + release."""
    instance = EngineInstance(
        id=instance_id, status="COMPLETED", engine_id=ENGINE_ID,
        engine_version="1", engine_variant=VARIANT,
        data_source_params='{"app_name": "DeployApp"}',
        algorithms_params='[{"name": "als", "params": {}}]')
    Storage.get_meta_data_engine_instances().insert(instance)
    blob = serialize_models([make_als_model(seed=seed, n_items=n_items)])
    Storage.get_model_data_models().insert(Model(id=instance.id, models=blob))
    return record_release(instance, train_seconds=1.0, blob=blob)


# ---------------------------------------------------------------------------
# release registry units
# ---------------------------------------------------------------------------

def test_release_versions_monotonic_per_variant(deploy_store):
    rels = Storage.get_meta_data_releases()
    a1 = Release(engine_id="a", engine_version="1", engine_variant="x")
    a2 = Release(engine_id="a", engine_version="1", engine_variant="x")
    b1 = Release(engine_id="b", engine_version="1", engine_variant="x")
    for r in (a1, a2, b1):
        rels.insert(r)
    assert (a1.version, a2.version, b1.version) == (1, 2, 1)
    listing = rels.get_for_variant("a", "1", "x")
    assert [r.version for r in listing] == [2, 1]       # newest first


def test_release_status_lineage(deploy_store):
    rels = Storage.get_meta_data_releases()
    r = Release(engine_id="a", engine_version="1", engine_variant="x")
    rels.insert(r)
    rels.set_status(r.id, "CANARY", reason="fraction=0.1")
    rels.set_status(r.id, "ROLLED_BACK", reason="slo_latency: p99")
    got = rels.get(r.id)
    assert got.status == "ROLLED_BACK"
    assert [h["status"] for h in got.history] == ["CANARY", "ROLLED_BACK"]
    assert got.history[-1]["reason"].startswith("slo_latency")
    with pytest.raises(ValueError):
        rels.set_status(r.id, "NONSENSE")


def test_resolve_release_selectors(deploy_store):
    rels = Storage.get_meta_data_releases()
    r1 = Release(engine_id="a", engine_version="1", engine_variant="x")
    r2 = Release(engine_id="a", engine_version="1", engine_variant="x")
    rels.insert(r1)
    rels.insert(r2)
    assert resolve_release(rels, "a", "1", "x", None).id == r2.id
    assert resolve_release(rels, "a", "1", "x", r1.id).id == r1.id
    assert resolve_release(rels, "a", "1", "x", "1").id == r1.id
    assert resolve_release(rels, "a", "1", "x", "v2").id == r2.id
    assert resolve_release(rels, "a", "1", "x", "v99") is None
    assert resolve_release(rels, "a", "1", "x", "junk") is None
    # a raw id from ANOTHER variant never resolves onto this one
    foreign = Release(engine_id="b", engine_version="1", engine_variant="y")
    rels.insert(foreign)
    assert resolve_release(rels, "a", "1", "x", foreign.id) is None
    assert resolve_release(rels, "b", "1", "y", foreign.id).id == foreign.id
    # a rejected release never rides back in as "the latest" — only an
    # explicit selector can redeploy it
    rels.set_status(r2.id, "ROLLED_BACK", reason="slo breach")
    assert resolve_release(rels, "a", "1", "x", None).id == r1.id
    assert resolve_release(rels, "a", "1", "x", "v2").id == r2.id
    rels.set_status(r1.id, "ROLLED_BACK", reason="slo breach")
    assert resolve_release(rels, "a", "1", "x", None) is None


def test_canary_config_clamps_fraction():
    # a canary is judged against the incumbent, so the incumbent must
    # keep traffic: fraction 1.0 would starve the baseline and wedge the
    # rollout with no verdict ever reachable
    cfg = CanaryConfig(fraction=1.0).normalized()
    assert cfg.fraction == CanaryConfig.MAX_FRACTION
    assert CanaryConfig(fraction=-3).normalized().fraction == 0.0


def test_run_train_registers_release(deploy_store):
    from fake_engine import Algo0, AlgoParams, DataSource0, Preparator0, \
        Serving0
    from predictionio_tpu.workflow import run_train

    eng = Engine(DataSource0, Preparator0, {"a": Algo0}, Serving0)
    ep = EngineParams(algorithm_params_list=[("a", AlgoParams(id=3))])
    instance = run_train(eng, ep, engine_factory="tests.fake:engine",
                         engine_variant="v1")
    rels = Storage.get_meta_data_releases().get_for_variant(
        "tests.fake:engine", "1", "v1")
    assert len(rels) == 1
    r = rels[0]
    assert r.version == 1 and r.status == "REGISTERED"
    assert r.instance_id == instance.id
    assert r.params_digest == params_digest(instance)
    blob = Storage.get_model_data_models().get(instance.id).models
    assert r.model_digest == model_digest(blob)
    assert r.model_size_bytes == len(blob)
    # a retrain becomes v2 of the same variant
    run_train(eng, ep, engine_factory="tests.fake:engine",
              engine_variant="v1")
    assert [x.version for x in Storage.get_meta_data_releases()
            .get_for_variant("tests.fake:engine", "1", "v1")] == [2, 1]


# ---------------------------------------------------------------------------
# canary controller units
# ---------------------------------------------------------------------------

def test_traffic_splitter_exact_fraction():
    s = TrafficSplitter(0.25)
    routed = [s.route() for _ in range(100)]
    assert sum(routed) == 25
    assert TrafficSplitter(0.0).route() is False
    assert all(TrafficSplitter(1.0).route() for _ in range(5))


def test_sliding_stats_window_and_quantiles():
    st = SlidingStats(window=4)
    for v in (0.010, 0.020, 0.030, 0.040, 0.050):
        st.observe(v, ok=True)
    assert st.count() == 4                      # 0.010 aged out
    assert st.quantile(0.5) == 0.030
    assert st.p99() == 0.050
    st.observe(0.0, ok=False)
    assert st.error_rate() == pytest.approx(0.25)
    assert st.total == 6


def test_controller_rolls_back_on_latency_breach():
    ctl = CanaryController(CanaryConfig(
        fraction=0.5, min_samples=5, promote_after=50,
        p99_ratio=1.5, latency_slack_s=0.001))
    verdict = None
    for _ in range(20):
        ctl.observe("incumbent", 0.010, True)
        verdict = verdict or ctl.observe("canary", 0.100, True)
    assert verdict is not None and verdict[0] == "rollback"
    assert verdict[1].startswith("slo_latency")
    # controller is inert after deciding
    assert ctl.observe("canary", 5.0, True) is None


def test_controller_rolls_back_on_error_breach():
    ctl = CanaryController(CanaryConfig(
        fraction=0.5, min_samples=5, promote_after=50,
        error_rate_slack=0.1))
    verdict = None
    for _ in range(10):
        ctl.observe("incumbent", 0.010, True)
        verdict = verdict or ctl.observe("canary", 0.010, False)
    assert verdict is not None and verdict[0] == "rollback"
    assert verdict[1].startswith("slo_errors")


def test_controller_promotes_clean_window():
    ctl = CanaryController(CanaryConfig(
        fraction=0.5, min_samples=5, promote_after=12,
        p99_ratio=3.0, latency_slack_s=0.5))
    verdict = None
    for _ in range(15):
        ctl.observe("incumbent", 0.010, True)
        verdict = verdict or ctl.observe("canary", 0.012, True)
    assert verdict == ("promote", "healthy: SLO window clean")


def test_canary_splitter_acc_survives_restart(tmp_path):
    """The restart-skew fix end to end: the splitter accumulator is
    process-local, so a server restart mid-canary used to re-seed it at
    zero and skew the realized fraction for the first ~1/fraction
    queries. The serving path publishes it as the
    ``pio_deploy_canary_splitter_acc`` gauge, the telemetry loop
    persists it, and ``_restore_canary_splitter`` feeds it back — a
    restarted server resumes the EXACT mid-stream split."""
    import types

    from predictionio_tpu.deploy.warm import deploy_metrics
    from predictionio_tpu.obs.registry import MetricsRegistry
    from predictionio_tpu.obs.telemetry import TelemetryRecorder
    from predictionio_tpu.utils.server_config import TelemetryConfig

    tcfg = TelemetryConfig(dir=str(tmp_path / "telemetry"),
                           interval_s=60.0)
    reg1 = MetricsRegistry()
    rec1 = TelemetryRecorder("pio", tcfg, registries=[reg1])
    ctl = CanaryController(CanaryConfig(fraction=0.25))
    routes = [ctl.splitter.route() for _ in range(10)]
    saved = ctl.splitter.state()
    # what query_server.handle_query does on every canary-routed query
    deploy_metrics(reg1).canary_splitter_acc.set(saved)
    rec1.stop()                     # restart: final scrape + close

    reference = TrafficSplitter(0.25)
    reference.restore(saved)
    reg2 = MetricsRegistry()
    rec2 = TelemetryRecorder("pio", tcfg, registries=[reg2])
    host = types.SimpleNamespace(_telemetry=rec2,
                                 _deploy=deploy_metrics(reg2))
    resumed = CanaryController(CanaryConfig(fraction=0.25))
    QueryServer._restore_canary_splitter(host, resumed)
    try:
        assert resumed.splitter.state() == saved != 0.0
        # the restored gauge re-publishes, so the next scrape persists it
        assert host._deploy.canary_splitter_acc.value() == saved
        expected = [reference.route() for _ in range(40)]
        assert [resumed.splitter.route() for _ in range(40)] == expected
        # realized fraction across the restart stays exact
        assert sum(routes) + sum(expected) == round(50 * 0.25)
    finally:
        rec2.stop()


def test_canary_splitter_restore_without_telemetry_is_noop():
    import types

    from predictionio_tpu.deploy.warm import deploy_metrics
    from predictionio_tpu.obs.registry import MetricsRegistry

    host = types.SimpleNamespace(_telemetry=None,
                                 _deploy=deploy_metrics(MetricsRegistry()))
    ctl = CanaryController(CanaryConfig(fraction=0.5))
    QueryServer._restore_canary_splitter(host, ctl)
    assert ctl.splitter.state() == 0.0


# ---------------------------------------------------------------------------
# warm swap: the compile-delta acceptance check
# ---------------------------------------------------------------------------

def _total_compiles() -> float:
    return sum(v for _l, v in compile_counter(default_registry()).samples())


async def test_warm_swap_zero_new_compiles_post_cutover():
    """The acceptance criterion: after a warm swap, the first post-
    cutover batches hit only shapes the warmup ladder already compiled —
    the pio_jax_compile_total delta across the swap is zero."""
    from predictionio_tpu.engines.recommendation import Query

    old_rt = als_mod._DEVICE_ROUNDTRIP_S
    als_mod._DEVICE_ROUNDTRIP_S = 0.0       # force the jitted device scorer
    try:
        server = make_server()              # incumbent: 30-item catalog
        # candidate: a NEW catalog size => its shape keys cannot ride the
        # incumbent's compiled executables
        unit_b = ServingUnit(
            instance=EngineInstance(id="warm-candidate", engine_id=ENGINE_ID,
                                    engine_version="1",
                                    engine_variant=VARIANT),
            result=TrainResult(models=[make_als_model(seed=5, n_items=41)],
                               algorithms=[ALSAlgorithm(AlgorithmParams())],
                               serving=RecommendationServing(),
                               engine_params=EngineParams()),
            ctx=None, vectorized=True)
        server._attach_batcher(unit_b)
        predict_batch = functools.partial(server._predict_batch_unit, unit_b)
        report = warmup_unit(unit_b, predict_batch,
                             server.serving_config.batch_max,
                             query=Query(user="u0", num=4))
        assert report.buckets == warmup_ladder(16) == [1, 2, 4, 8, 16]
        assert report.compile_delta > 0      # warmup paid the compiles
        assert report.skipped is None

        c = TestClient(TestServer(server.app))
        await c.start_server()
        try:
            before = _total_compiles()
            server._swap_to(unit_b, mode="warm", reason="test")
            for burst in (3, 5, 11):         # buckets 4, 8, 16
                out = await asyncio.gather(*[
                    c.post("/queries.json",
                           json={"user": f"u{i % N_USERS}", "num": 4})
                    for i in range(burst)])
                for resp in out:
                    assert resp.status == 200
                    scores = (await resp.json())["itemScores"]
                    assert len(scores) == 4
                    # post-cutover traffic scores on the NEW catalog
                    assert all(s["item"] in
                               {f"i{j}" for j in range(41)}
                               for s in scores)
            assert _total_compiles() == before, \
                "warm swap must pay zero post-cutover compiles"

            # contrast: a COLD swap of yet another catalog compiles on
            # the serving path
            unit_c = ServingUnit(
                instance=EngineInstance(id="cold", engine_id=ENGINE_ID,
                                        engine_version="1",
                                        engine_variant=VARIANT),
                result=TrainResult(
                    models=[make_als_model(seed=6, n_items=43)],
                    algorithms=[ALSAlgorithm(AlgorithmParams())],
                    serving=RecommendationServing(),
                    engine_params=EngineParams()),
                ctx=None, vectorized=True)
            server._attach_batcher(unit_c)
            before_cold = _total_compiles()
            server._swap_to(unit_c, mode="cold", reason="test")
            resp = await c.post("/queries.json", json={"user": "u1",
                                                       "num": 4})
            assert resp.status == 200
            assert _total_compiles() > before_cold, \
                "cold swap should have compiled on the serving path"
        finally:
            await c.close()
    finally:
        als_mod._DEVICE_ROUNDTRIP_S = old_rt


# ---------------------------------------------------------------------------
# /reload-vs-inflight-batch races (satellite): no half-swapped pairs
# ---------------------------------------------------------------------------

class PlainServing(Serving):
    def serve(self, query, predictions):
        return predictions[0]


class BlockingTagAlgo(Algorithm):
    """Vectorized algorithm whose batches block on an Event, then tag
    results with its model — the probe for swap-while-draining."""

    def __init__(self, gate: threading.Event):
        self.gate = gate

    def train(self, ctx, pd):
        return None

    def predict(self, model, query):
        return {"model": model}

    def batch_predict(self, model, queries):
        assert self.gate.wait(timeout=10), "test gate never opened"
        return [(i, {"model": model}) for i, _ in queries]


class TagAlgoNotVectorized(Algorithm):
    def train(self, ctx, pd):
        return None

    def predict(self, model, query):
        return {"model": model}


async def test_swap_while_batches_drain_no_half_swapped_pair():
    """Swap a release while batches are draining: every in-flight request
    must resolve on the unit it was routed to — model and vectorized
    flag as ONE consistent pair, never mixed, never errored."""
    gate = threading.Event()
    result_a = TrainResult(models=["A"],
                           algorithms=[BlockingTagAlgo(gate)],
                           serving=PlainServing(),
                           engine_params=EngineParams())
    instance = EngineInstance(id="race-a", engine_id=ENGINE_ID,
                              engine_version="1", engine_variant=VARIANT)
    server = QueryServer(
        make_engine(), result_a, instance, ctx=None,
        serving_config=ServingConfig(batch_max=8, batch_linger_s=0.0,
                                     batch_inflight=1),
        deploy_config=DeployConfig(warmup=False, drain_timeout_s=10.0))
    assert server._unit.vectorized is True

    unit_b = ServingUnit(
        instance=EngineInstance(id="race-b", engine_id=ENGINE_ID,
                                engine_version="1", engine_variant=VARIANT),
        result=TrainResult(models=["B"],
                           algorithms=[TagAlgoNotVectorized()],
                           serving=PlainServing(),
                           engine_params=EngineParams()),
        ctx=None, vectorized=False)
    server._attach_batcher(unit_b)

    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        old_unit = server._unit
        first = [asyncio.ensure_future(c.post("/queries.json", json={"q": i}))
                 for i in range(6)]
        # let the batcher pick up + dispatch (blocked on the gate)
        for _ in range(20):
            await asyncio.sleep(0.01)
            if old_unit.batcher._inflight_now > 0:
                break
        assert old_unit.batcher._inflight_now > 0

        server._swap_to(unit_b, mode="warm", reason="race-test")
        assert server._unit is unit_b

        second = [asyncio.ensure_future(
            c.post("/queries.json", json={"q": 100 + i})) for i in range(6)]
        await asyncio.sleep(0.05)
        gate.set()                       # old batches drain AFTER the swap

        outs = []
        for fut in first + second:
            resp = await fut
            assert resp.status == 200, await resp.text()
            outs.append((await resp.json())["model"])
        # pre-swap requests all scored on A (the unit they were routed
        # to), post-swap on B — no mixes, no errors
        assert outs[:6] == ["A"] * 6
        assert outs[6:] == ["B"] * 6

        # the retired unit's batcher drains and is torn down
        for _ in range(100):
            if old_unit.batcher is None:
                break
            await asyncio.sleep(0.02)
        assert old_unit.batcher is None
    finally:
        await c.close()


async def test_rollback_during_drain_window_keeps_live_batcher():
    """Rolling back while the outgoing unit's batcher is still draining
    must NOT let the pending retire task tear down the batcher that is
    now serving live traffic again."""
    gate = threading.Event()
    result_a = TrainResult(models=["A"],
                           algorithms=[BlockingTagAlgo(gate)],
                           serving=PlainServing(),
                           engine_params=EngineParams())
    server = QueryServer(
        make_engine(), result_a,
        EngineInstance(id="drain-a", engine_id=ENGINE_ID,
                       engine_version="1", engine_variant=VARIANT),
        ctx=None,
        serving_config=ServingConfig(batch_max=8, batch_linger_s=0.0,
                                     batch_inflight=1),
        deploy_config=DeployConfig(warmup=False, drain_timeout_s=0.3))
    unit_a = server._unit
    unit_b = ServingUnit(
        instance=EngineInstance(id="drain-b", engine_id=ENGINE_ID,
                                engine_version="1", engine_variant=VARIANT),
        result=TrainResult(models=["B"],
                           algorithms=[TagAlgoNotVectorized()],
                           serving=PlainServing(),
                           engine_params=EngineParams()),
        ctx=None, vectorized=False)
    server._attach_batcher(unit_b)

    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        # block a batch on A, swap to B (A starts draining), then roll
        # back to A BEFORE the 0.3s drain deadline fires
        blocked = asyncio.ensure_future(
            c.post("/queries.json", json={"q": 0}))
        for _ in range(50):
            await asyncio.sleep(0.01)
            if unit_a.batcher._inflight_now > 0:
                break
        server._swap_to(unit_b, mode="cold", reason="drain-test")
        resp = await c.post("/rollback.json")
        assert resp.status == 200, await resp.json()
        assert server._unit is unit_a
        batcher = unit_a.batcher
        assert batcher is not None
        # outlive the original drain deadline, then prove A still serves
        await asyncio.sleep(0.5)
        gate.set()
        assert (await (await blocked).json())["model"] == "A"
        assert unit_a.batcher is batcher      # never torn down
        resp = await c.post("/queries.json", json={"q": 1})
        assert resp.status == 200
        assert (await resp.json())["model"] == "A"
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# the integration paths: canary rollback / promote / shadow / CLI rollback
# ---------------------------------------------------------------------------

class SlowALS(ALSAlgorithm):
    """The injected latency regression: every batch pays +60ms."""

    def batch_predict(self, model, queries):
        time.sleep(0.06)
        return super().batch_predict(model, queries)


class ErrorALS(ALSAlgorithm):
    """The injected error regression: scoring always fails."""

    def predict(self, model, query):
        raise RuntimeError("regressed model")

    def batch_predict(self, model, queries):
        raise RuntimeError("regressed model")


async def _wait_release_status(release_id, status, timeout=15.0):
    """Release lineage writes are scheduled off the serving path; poll
    the store instead of racing them. The deadline is generous slack
    only — a passing write returns at the next 20ms poll; a loaded
    2-core CI box has been seen delaying the default-executor write
    past 3s."""
    deadline = time.monotonic() + timeout
    rels = Storage.get_meta_data_releases()
    while time.monotonic() < deadline:
        got = rels.get(release_id)
        if got is not None and got.status == status:
            return got
        await asyncio.sleep(0.02)
    got = rels.get(release_id)
    raise AssertionError(
        f"release {release_id} never reached {status}; "
        f"stuck at {got.status if got else None}")


async def _drive(c, n, start=0):
    statuses = []
    for i in range(n):
        resp = await c.post("/queries.json",
                            json={"user": f"u{(start + i) % N_USERS}",
                                  "num": 3})
        await resp.json()
        statuses.append(resp.status)
    return statuses


async def test_canary_latency_regression_auto_rolls_back(deploy_store):
    release = register_candidate(seed=2)
    server = make_server(engine=make_engine(SlowALS))
    incumbent_id = server.instance.id
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "version": 1, "canaryFraction": 0.5, "canaryWindow": 40,
            "canaryMinSamples": 5, "canaryPromoteAfter": 200,
            "canaryP99Ratio": 1.5, "canaryLatencySlackS": 0.005})
        body = await resp.json()
        assert resp.status == 200, body
        assert body["message"] == "Canary started"
        assert body["warmup"]["skipped"] is None
        assert server._canary is not None

        await _drive(c, 30)
        for _ in range(50):                  # verdict task runs off-path
            if server._canary is None:
                break
            await asyncio.sleep(0.02)
        assert server._canary is None, "latency breach must end the canary"
        # incumbent still serving; candidate recorded ROLLED_BACK
        assert server.instance.id == incumbent_id
        rel = await _wait_release_status(release.id, "ROLLED_BACK")
        assert any(h["reason"].startswith("slo_latency")
                   for h in rel.history)
        # both paths visible in pio_deploy_* metrics
        m = server._deploy
        assert m.requests_total.value(role="canary") > 0
        assert m.requests_total.value(role="incumbent") > 0
        assert m.rollback_total.value(reason="slo_latency") == 1
        assert server.registry.get(
            "pio_deploy_canary_fraction").value() == 0.0
    finally:
        await c.close()


async def test_canary_error_regression_auto_rolls_back(deploy_store):
    release = register_candidate(seed=2)
    server = make_server(engine=make_engine(ErrorALS))
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "version": 1, "canaryFraction": 0.5, "canaryMinSamples": 5,
            "canaryPromoteAfter": 200, "canaryErrorRateSlack": 0.2,
            # the regressed model fails verify too — deploy cold-starts it
            # into the canary instead of refusing? No: verify must pass, so
            # inject errors only at scoring depth below the warmup query.
            "warmup": False})
        body = await resp.json()
        # ErrorALS fails the verify health gate outright: the deploy is
        # refused and the incumbent keeps 100% of traffic
        assert resp.status == 500
        assert server._canary is None
        rel = await _wait_release_status(release.id, "ROLLED_BACK")
        assert "prepare failed" in rel.history[-1]["reason"]
        # the deploy body disabled warmup, so the failed swap must be
        # labeled cold (the mode label follows the EFFECTIVE warmup flag)
        assert server._deploy.swap_total.value(
            mode="cold", outcome="failed") == 1
        # traffic still healthy
        assert all(s == 200 for s in await _drive(c, 4))
    finally:
        await c.close()


class LateErrorALS(ALSAlgorithm):
    """Passes warmup/verify (first calls succeed), then regresses —
    the failure mode only a live SLO guard can catch. Fails the batch
    path AND the server's per-query isolation fallback, like a truly
    corrupt model would."""

    calls = 0

    def batch_predict(self, model, queries):
        type(self).calls += 1
        if type(self).calls > 8:
            raise RuntimeError("late regression")
        return super().batch_predict(model, queries)

    def predict(self, model, query):
        if type(self).calls > 8:
            raise RuntimeError("late regression")
        return super().predict(model, query)


async def test_canary_late_error_regression_auto_rolls_back(deploy_store):
    LateErrorALS.calls = 0
    release = register_candidate(seed=2)
    server = make_server(engine=make_engine(LateErrorALS))
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "version": 1, "canaryFraction": 0.5, "canaryMinSamples": 5,
            "canaryPromoteAfter": 200, "canaryErrorRateSlack": 0.2})
        assert resp.status == 200, await resp.json()
        statuses = await _drive(c, 40)
        for _ in range(50):
            if server._canary is None:
                break
            await asyncio.sleep(0.02)
        assert server._canary is None
        assert server._deploy.rollback_total.value(reason="slo_errors") == 1
        await _wait_release_status(release.id, "ROLLED_BACK")
        # after the rollback the incumbent serves everything again
        assert all(s == 200 for s in await _drive(c, 5))
        assert 400 in statuses       # the regression WAS user-visible...
    finally:
        await c.close()


async def test_canary_healthy_auto_promotes(deploy_store):
    release = register_candidate(seed=3)
    server = make_server()
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "releaseId": release.id, "canaryFraction": 0.5,
            "canaryMinSamples": 5, "canaryPromoteAfter": 10,
            # generous SLOs: identical models must never false-positive
            "canaryP99Ratio": 10.0, "canaryLatencySlackS": 1.0})
        body = await resp.json()
        assert resp.status == 200, body
        await _wait_release_status(release.id, "CANARY")

        await _drive(c, 40)
        for _ in range(50):
            if server._canary is None:
                break
            await asyncio.sleep(0.02)
        assert server._canary is None, "healthy canary must promote"
        assert server.instance.id == "deploy-candidate"
        assert server._unit.release_version == 1
        await _wait_release_status(release.id, "LIVE")
        m = server._deploy
        assert m.promote_total.value(reason="healthy") == 1
        assert m.requests_total.value(role="canary") > 0
        assert server.registry.get(
            "pio_deploy_active_release_version").value() == 1.0
        # the retired incumbent is the resident standby
        assert server._standby is not None
        assert server._standby.instance.id == "deploy-incumbent"
    finally:
        await c.close()


async def test_shadow_mode_scores_but_never_serves(deploy_store):
    register_candidate(seed=4)
    server = make_server(engine=make_engine(LateErrorALS))
    LateErrorALS.calls = 100                  # regressed from the start...
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "version": 1, "shadow": True, "canaryMinSamples": 5,
            "canaryPromoteAfter": 200, "canaryErrorRateSlack": 0.2,
            "warmup": False,
            # ...so skip the health gates: shadow exists to absorb
            # exactly this blast radius
            })
        body = await resp.json()
        # verify still gates even shadow deploys — reset the regression
        # so prepare passes, then re-regress for live shadow traffic
        if resp.status == 500:
            LateErrorALS.calls = 0
            resp = await c.post("/deploy.json", json={
                "version": 1, "shadow": True, "canaryMinSamples": 5,
                "canaryPromoteAfter": 200, "canaryErrorRateSlack": 0.2})
            body = await resp.json()
            assert resp.status == 200, body
            LateErrorALS.calls = 100
        assert server._canary is not None
        assert server._canary.config.shadow is True

        statuses = await _drive(c, 30)
        # EVERY user-visible response came from the incumbent
        assert all(s == 200 for s in statuses)
        for _ in range(100):
            if server._canary is None:
                break
            await asyncio.sleep(0.02)
        assert server._canary is None
        m = server._deploy
        assert m.requests_total.value(role="shadow") > 0
        assert m.requests_total.value(role="canary") == 0
        assert m.rollback_total.value(reason="slo_errors") == 1
    finally:
        await c.close()


async def test_deploy_rejects_second_concurrent_canary(deploy_store):
    release = register_candidate(seed=3)
    server = make_server()
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "releaseId": release.id, "canaryFraction": 0.3,
            "canaryPromoteAfter": 10_000})
        assert resp.status == 200, await resp.json()
        resp = await c.post("/deploy.json", json={
            "releaseId": release.id, "canaryFraction": 0.3})
        assert resp.status == 409
    finally:
        await c.close()


async def test_reload_refused_during_live_canary(deploy_store):
    """A swap under a judging canary would poison the incumbent
    baseline — /reload must refuse like /deploy does."""
    release = register_candidate(seed=3)
    server = make_server()
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "releaseId": release.id, "canaryFraction": 0.3,
            "canaryPromoteAfter": 10_000})
        assert resp.status == 200, await resp.json()
        resp = await c.get("/reload")
        assert resp.status == 409
    finally:
        await c.close()


async def test_operator_rollback_aborts_canary(deploy_store):
    release = register_candidate(seed=3)
    server = make_server()
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={
            "releaseId": release.id, "canaryFraction": 0.3,
            "canaryPromoteAfter": 10_000})
        assert resp.status == 200, await resp.json()
        resp = await c.post("/rollback.json")
        body = await resp.json()
        assert resp.status == 200 and body["message"] == "Canary aborted"
        assert server._canary is None
        await _wait_release_status(release.id, "ROLLED_BACK")
        assert server._deploy.rollback_total.value(reason="slo_latency") == 0
    finally:
        await c.close()


async def test_full_deploy_then_rollback_restores_standby(deploy_store):
    release = register_candidate(seed=3)
    server = make_server()
    incumbent_id = server.instance.id
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={"releaseId": release.id})
        body = await resp.json()
        assert resp.status == 200 and body["message"] == "Deployed", body
        assert server.instance.id == "deploy-candidate"
        await _wait_release_status(release.id, "LIVE")

        resp = await c.post("/rollback.json")
        body = await resp.json()
        assert resp.status == 200 and body["message"] == "Rolled back"
        assert server.instance.id == incumbent_id
        await _wait_release_status(release.id, "ROLLED_BACK")
        assert all(s == 200 for s in await _drive(c, 3))
    finally:
        await c.close()


async def test_releases_and_status_endpoints(deploy_store):
    release = register_candidate(seed=3)
    server = make_server()
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        resp = await c.get("/releases.json")
        body = await resp.json()
        assert resp.status == 200
        assert [r["version"] for r in body["releases"]] == [1]
        assert body["releases"][0]["id"] == release.id
        assert body["serving"]["engineInstanceId"] == "deploy-incumbent"

        resp = await c.get("/deploy/status.json")
        body = await resp.json()
        assert body["active"]["engineInstanceId"] == "deploy-incumbent"
        assert body["canary"] is None
    finally:
        await c.close()


async def test_cli_rollback_against_live_server(deploy_store):
    """Acceptance: `pio rollback` restores the previous release end-to-
    end from the CLI against a live query server."""
    from click.testing import CliRunner
    from predictionio_tpu.cli.main import cli

    release = register_candidate(seed=3)
    server = make_server()
    incumbent_id = server.instance.id
    ts = TestServer(server.app)
    c = TestClient(ts)
    await c.start_server()
    try:
        resp = await c.post("/deploy.json", json={"releaseId": release.id})
        assert resp.status == 200, await resp.json()
        assert server.instance.id == "deploy-candidate"

        loop = asyncio.get_running_loop()
        invoke = functools.partial(
            CliRunner().invoke, cli,
            ["rollback", "--ip", ts.host, "--port", str(ts.port)])
        result = await loop.run_in_executor(None, invoke)
        assert result.exit_code == 0, result.output
        assert "Rolled back" in result.output
        assert incumbent_id in result.output
        assert server.instance.id == incumbent_id
    finally:
        await c.close()


async def test_admin_releases_fleet_view(deploy_store):
    from predictionio_tpu.server.admin import create_admin_server

    register_candidate(seed=3)
    c = TestClient(TestServer(create_admin_server()))
    await c.start_server()
    try:
        resp = await c.get("/cmd/releases")
        body = await resp.json()
        assert resp.status == 200 and body["status"] == 1
        assert [r["version"] for r in body["releases"]] == [1]
        assert body["releases"][0]["engineId"] == ENGINE_ID
        resp = await c.get("/cmd/releases?engineId=no-such-engine")
        assert (await resp.json())["releases"] == []
    finally:
        await c.close()


async def test_deploy_endpoints_respect_access_key(deploy_store):
    register_candidate(seed=3)
    server = make_server()
    server.access_key = "sekrit"
    c = TestClient(TestServer(server.app))
    await c.start_server()
    try:
        for path in ("/deploy.json", "/rollback.json"):
            resp = await c.post(path, json={})
            assert resp.status == 401
        resp = await c.post("/rollback.json?accessKey=sekrit")
        assert resp.status in (200, 404)      # authorized (no standby)
    finally:
        await c.close()
