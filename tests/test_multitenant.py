"""Multi-tenant serving host (server/multitenant.py) + the warm
eviction/reload cycle (server/query_server.py).

Covers the ISSUE's acceptance paths:
  * routing/isolation — N tenants behind /t/{name}/queries.json in ONE
    process, each answering from its OWN factors, with the per-tenant
    deploy/status surface reachable through the subapp fallthrough;
  * eviction/reload correctness — answers byte-identical across a full
    evict -> warm-reload cycle, the unit never observable half-resident
    (kill-point chaos at all four boundaries), queries during a reload
    either wait-bounded or 503 cleanly, and a deploy racing a warm
    reload wins (the reloaded unit is discarded, never silently
    installed);
  * the residency budgeter — an undersized PIO_MT_DEVICE_BUDGET_BYTES
    evicts the least-recently-queried tenant, on the miss path AND the
    background sweep, never below min_resident;
  * admission control — a tenant whose SLO budget burns is 429'd (with
    Retry-After) while the quiet tenant keeps answering 200;
  * tenant label cardinality — the `tenant` label rides the registry's
    max_series overflow guard: an explosion collapses into `other`
    WITHOUT losing established tenants' series.
"""

import asyncio
import json
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.core.engine import Engine, TrainResult
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.deploy.releases import record_release
from predictionio_tpu.engines.recommendation import (
    ALSAlgorithm, AlgorithmParams, DataSourceParams,
    RecommendationDataSource, RecommendationPreparator,
    RecommendationServing,
)
from predictionio_tpu.models.als import ALSModel
from predictionio_tpu.server.multitenant import (
    MultiTenantServer, TenantSpec,
)
from predictionio_tpu.storage import Model, Storage
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.faults import CrashError, set_kill_points
from predictionio_tpu.utils.server_config import (
    DeployConfig, MultiTenantConfig, ServingConfig,
)
from predictionio_tpu.workflow.serialization import serialize_models

pytestmark = pytest.mark.anyio

ENGINE_ID = ("predictionio_tpu.engines.recommendation."
             "RecommendationEngineFactory")
RANK = 8


def make_model(seed=0, n_users=24, n_items=120, rank=RANK) -> ALSModel:
    rng = np.random.default_rng(seed)
    return ALSModel(
        user_vocab=np.sort(np.asarray(
            [f"u{i}" for i in range(n_users)], dtype=object)),
        item_vocab=np.sort(np.asarray(
            [f"i{i}" for i in range(n_items)], dtype=object)),
        U=rng.normal(size=(n_users, rank)).astype(np.float32),
        V=rng.normal(size=(n_items, rank)).astype(np.float32))


def make_engine() -> Engine:
    return Engine(
        data_source_classes=RecommendationDataSource,
        preparator_classes=RecommendationPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_classes=RecommendationServing,
    )


@pytest.fixture()
def mt_store(tmp_path):
    Storage.configure({
        "sources": {"DB": {"TYPE": "sqlite",
                           "PATH": str(tmp_path / "mt.db")}},
        "repositories": {
            "METADATA": {"NAME": "pio", "SOURCE": "DB"},
            "EVENTDATA": {"NAME": "pio", "SOURCE": "DB"},
            "MODELDATA": {"NAME": "pio", "SOURCE": "DB"},
        },
    })
    yield Storage
    Storage.reset()


@pytest.fixture()
def device_resident(monkeypatch):
    """Pin the roundtrip estimate to zero so scoring takes the device
    lane — that is what populates the models' resident/scorer caches the
    capacity ledger attributes bytes from."""
    import predictionio_tpu.models.als as als_mod

    monkeypatch.setattr(als_mod, "_DEVICE_ROUNDTRIP_S", 0.0)


def make_tenant_spec(name, seed, n_items=120, slo=None) -> TenantSpec:
    """A persisted, reloadable tenant: instance + serialized model +
    release in Storage so the warm-reload ladder has something to
    deserialize."""
    model = make_model(seed=seed, n_items=n_items)
    instance = EngineInstance(
        id=f"mt-{name}", status="COMPLETED", engine_id=ENGINE_ID,
        engine_version="1", engine_variant=name,
        data_source_params=json.dumps({"app_name": f"{name}App"}),
        algorithms_params='[{"name": "als", "params": {"rank": %d}}]'
        % RANK)
    Storage.get_meta_data_engine_instances().insert(instance)
    blob = serialize_models([model])
    Storage.get_model_data_models().insert(Model(id=instance.id,
                                                 models=blob))
    release = record_release(instance, train_seconds=1.0, blob=blob)
    result = TrainResult(
        models=[model],
        algorithms=[ALSAlgorithm(AlgorithmParams(rank=RANK))],
        serving=RecommendationServing(),
        engine_params=EngineParams(
            data_source_params=DataSourceParams(app_name=f"{name}App")))
    return TenantSpec(
        name=name, engine=make_engine(), train_result=result,
        instance=instance, ctx=None, release=release,
        serving_config=ServingConfig(batch_max=8, batch_linger_s=0.0),
        deploy_config=DeployConfig(warmup=False, drain_timeout_s=5.0),
        slo=slo)


def make_host(specs, **cfg) -> MultiTenantServer:
    defaults = dict(budget_bytes=0, reload_wait_s=5.0,
                    sweep_interval_s=60.0, min_resident=0)
    defaults.update(cfg)
    return MultiTenantServer(specs, config=MultiTenantConfig(**defaults))


async def query(client, tenant, user="u1", num=3):
    return await client.post(f"/t/{tenant}/queries.json",
                             json={"user": user, "num": num})


async def scores(client, tenant, user="u1", num=3):
    r = await query(client, tenant, user, num)
    assert r.status == 200, await r.text()
    return (await r.json())["itemScores"]


# ---------------------------------------------------------------------------
# construction + routing
# ---------------------------------------------------------------------------

def test_tenant_name_validation(mt_store):
    good = make_tenant_spec("ok-name", seed=1)
    for bad in ("", "a/b", "a b", "-lead", "{x}"):
        spec = TenantSpec(
            name=bad, engine=good.engine, train_result=good.train_result,
            instance=good.instance, ctx=None)
        with pytest.raises(ValueError):
            make_host([spec])
    with pytest.raises(ValueError):
        make_host([good, good])        # duplicate names
    with pytest.raises(ValueError):
        make_host([])


async def test_routing_isolation_and_surfaces(mt_store):
    """Three engine variants in one process: each tenant answers from
    its own factors, the host surfaces list them, and the per-tenant
    deploy surface is reachable through the subapp fallthrough."""
    host = make_host([make_tenant_spec("alpha", seed=1),
                      make_tenant_spec("beta", seed=2),
                      make_tenant_spec("gamma", seed=3)])
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        got = {t: await scores(c, t) for t in ("alpha", "beta", "gamma")}
        # distinct factor seeds -> distinct rankings: proof each tenant
        # scored on ITS unit, not a shared one
        assert len({json.dumps(v) for v in got.values()}) == 3
        assert all(len(v) == 3 for v in got.values())

        r = await c.get("/")
        doc = await r.json()
        assert doc["tenants"] == ["alpha", "beta", "gamma"]

        r = await c.get("/tenants.json")
        listing = (await r.json())["tenants"]
        assert [t["tenant"] for t in listing] == ["alpha", "beta", "gamma"]
        assert all(t["resident"] for t in listing)

        # subapp fallthrough: the tenant's OWN deploy surface
        r = await c.get("/t/beta/deploy/status.json")
        status = await r.json()
        assert status["resident"] is True
        assert status["active"]["engineInstanceId"] == "mt-beta"

        r = await query(c, "nosuch")
        assert r.status == 404

        # per-tenant gate metrics moved
        assert host._queries.value(tenant="alpha") == 1
        assert host._queries.value(tenant="gamma") == 1
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# evict -> reload correctness
# ---------------------------------------------------------------------------

async def test_evict_reload_byte_identical(mt_store, device_resident):
    """A full evict -> warm-reload cycle: factors drop (resident bytes
    attributed, then zero), the next query reloads through the warmup
    ladder, and answers are byte-identical pre/post."""
    host = make_host([make_tenant_spec("alpha", seed=1)])
    tenant = host.tenants["alpha"]
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        before = {u: await scores(c, "alpha", user=u)
                  for u in ("u1", "u5", "nosuchuser")}
        assert tenant.server.resident
        assert await tenant.server.evict_to_warm("test") is True
        assert not tenant.server.resident
        assert tenant.server.warm_bytes > 0      # pre-eviction attribution
        assert tenant.server._unit.result is None
        assert tenant.server._standby is None    # standby dropped too
        r = await c.get("/residency.json")
        doc = await r.json()
        assert doc["residentBytes"] == 0
        assert doc["tenants"][0]["warmBytes"] > 0

        # next hits drive the single-flight reload, then answers match
        after = {u: await scores(c, "alpha", user=u)
                 for u in ("u1", "u5", "nosuchuser")}
        assert after == before
        assert tenant.server.resident
        evictions = tenant.server._evict_total
        assert evictions.value(reason="test") == 1
        reloads = tenant.server._reload_total
        assert reloads.value(status="warm_reload") == 1
    finally:
        await c.close()


async def test_evict_refused_mid_canary_and_mid_reload(mt_store):
    host = make_host([make_tenant_spec("alpha", seed=1)])
    server = host.tenants["alpha"].server
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        # a reload latch in flight refuses a second eviction
        server._reload_event = asyncio.Event()
        assert await server.evict_to_warm() is False
        server._reload_event.set()
        server._reload_event = None
        # a canary window refuses eviction (the judge needs its baseline)
        server._canary = object.__new__(
            type("C", (), {}))  # truthy sentinel; only `is not None` read
        assert await server.evict_to_warm() is False
        server._canary = None
        # an already-warm unit refuses a double evict
        assert await server.evict_to_warm() is True
        assert await server.evict_to_warm() is False
    finally:
        await c.close()


async def test_reload_timeout_answers_503_with_retry_after(mt_store):
    """Queries during a stuck reload are wait-bounded: past the bound
    the client gets a clean 503 + Retry-After, and once the reload
    completes the tenant serves again."""
    host = make_host([make_tenant_spec("alpha", seed=1)],
                     reload_wait_s=0.2)
    server = host.tenants["alpha"].server
    gate = asyncio.Event()
    real_prepare = server._prepare_unit

    async def stalled_prepare(*args, **kwargs):
        await gate.wait()
        return await real_prepare(*args, **kwargs)

    server._prepare_unit = stalled_prepare
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        baseline = await scores(c, "alpha")
        assert await server.evict_to_warm() is True
        r = await query(c, "alpha")
        assert r.status == 503
        assert "Retry-After" in r.headers
        assert host._reload_timeouts.value(tenant="alpha") == 1
        gate.set()                      # un-stick the in-flight reload
        deadline = time.monotonic() + 5
        while not server.resident and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert await scores(c, "alpha") == baseline
    finally:
        gate.set()
        await c.close()


async def test_kill_points_never_half_resident(mt_store, device_resident):
    """Chaos at all four evict/reload boundaries: whatever side of the
    kill the state landed on, the active unit is either fully resident
    or fully warm, and the NEXT query cycle recovers to the same
    answers."""
    host = make_host([make_tenant_spec("alpha", seed=1)])
    server = host.tenants["alpha"].server
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        baseline = await scores(c, "alpha")

        for point in ("mt:evict:drained", "mt:evict:committed"):
            assert server.resident
            set_kill_points([point])
            with pytest.raises(CrashError):
                await server.evict_to_warm("chaos")
            set_kill_points([])
            # both sides of either kill: the serving reference is the
            # warm placeholder — never a half-unit
            assert server._unit.result is None
            assert not server.resident
            # recovery: the next query reloads and answers identically
            assert await scores(c, "alpha") == baseline
            assert server.resident

        for point in ("mt:reload:loaded", "mt:reload:committed"):
            assert await server.evict_to_warm("chaos") is True
            set_kill_points([point])
            ev = asyncio.Event()
            server._reload_event = ev
            with pytest.raises(CrashError):
                await server._reload_from_warm(ev)
            set_kill_points([])
            # the latch always clears (waiters wake either way) and the
            # unit is fully warm OR fully resident, by kill side
            assert ev.is_set()
            assert server._reload_event is None
            if point == "mt:reload:loaded":
                assert server._unit.result is None      # swap never ran
            else:
                assert server.resident                  # swap committed
            assert await scores(c, "alpha") == baseline
            assert server.resident
    finally:
        set_kill_points([])
        await c.close()


async def test_deploy_racing_warm_reload_wins(mt_store):
    """The swap-vs-evict race under the _swap_lock discipline: a deploy
    that lands while a warm reload is in flight must win — the reloaded
    unit is discarded (counted raced), never silently installed over
    the newer release."""
    host = make_host([make_tenant_spec("alpha", seed=1)])
    server = host.tenants["alpha"].server
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        await scores(c, "alpha")
        assert await server.evict_to_warm() is True
        warm = server._unit

        hold = asyncio.Event()
        real_prepare = server._prepare_unit

        async def slow_prepare(*args, **kwargs):
            unit = await real_prepare(*args, **kwargs)
            await hold.wait()
            return unit

        server._prepare_unit = slow_prepare
        ev = asyncio.Event()
        server._reload_event = ev
        reload_task = asyncio.get_running_loop().create_task(
            server._reload_from_warm(ev))
        await asyncio.sleep(0.05)

        # the racing deploy: a fresh unit swapped in while the reload
        # is still holding its prepared unit
        server._prepare_unit = real_prepare
        deployed = await server._prepare_unit(server._unit.instance,
                                              server._unit.release)
        server._swap_to(deployed, mode="deploy", reason="race-test")
        assert server._unit is deployed

        server._prepare_unit = slow_prepare
        hold.set()
        await reload_task
        # the deploy's unit is still live; the reload discarded its own
        assert server._unit is deployed
        assert server._unit is not warm
        assert server._reload_total.value(status="warm_reload_raced") == 1
        assert server.resident
        assert await scores(c, "alpha")
    finally:
        hold.set()
        await c.close()


# ---------------------------------------------------------------------------
# the residency budgeter
# ---------------------------------------------------------------------------

async def test_budget_lru_eviction_on_miss_and_sweep(
        mt_store, device_resident):
    """An undersized budget: the sweep evicts the least-recently-queried
    tenant down to the budget, and a miss on the evicted tenant makes
    room by evicting the NEXT least-recent — one budget, N tenants,
    queries keep answering."""
    host = make_host([make_tenant_spec("alpha", seed=1, n_items=300),
                      make_tenant_spec("beta", seed=2, n_items=300)])
    alpha, beta = host.tenants["alpha"], host.tenants["beta"]
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        a_scores = await scores(c, "alpha")
        b_scores = await scores(c, "beta")
        a_bytes = alpha.server.warm_bytes
        b_bytes = beta.server.warm_bytes
        assert a_bytes > 0 and b_bytes > 0
        # a budget that fits ONE tenant but not both
        host.config.budget_bytes = int(max(a_bytes, b_bytes) * 1.5)
        assert a_bytes + b_bytes > host.config.budget_bytes

        # freshen alpha, then sweep: beta is the LRU victim
        await scores(c, "alpha")
        await host.enforce_budget()
        assert alpha.server.resident
        assert not beta.server.resident
        assert host.resident_bytes() <= host.config.budget_bytes

        # miss on beta: the budgeter makes room by evicting alpha (the
        # projection uses beta's remembered footprint), then reloads
        assert await scores(c, "beta") == b_scores
        assert beta.server.resident
        assert not alpha.server.resident

        # and back: the cycle is stable in both directions
        assert await scores(c, "alpha") == a_scores
        assert alpha.server.resident
        assert not beta.server.resident
    finally:
        await c.close()


async def test_min_resident_floor_holds(mt_store, device_resident):
    """The sweep never evicts below min_resident even when the budget is
    absurdly small — some tenant must keep serving."""
    host = make_host([make_tenant_spec("alpha", seed=1)],
                     min_resident=1)
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        await scores(c, "alpha")
        host.config.budget_bytes = 1          # nothing fits
        await host.enforce_budget()
        assert host.tenants["alpha"].server.resident
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# admission control (the SLO-burn 429 path)
# ---------------------------------------------------------------------------

SLO = {"objectives": [{"name": "errors", "kind": "errors",
                       "budget": 0.1}],
       "windows": [{"seconds": 60, "burnThreshold": 1.0}],
       "evalIntervalS": 60}


async def test_burning_tenant_is_shed_quiet_tenant_unaffected(mt_store):
    """Prove the e2e: a tenant burning its error budget gets 429 +
    Retry-After at the gate; the co-hosted quiet tenant keeps answering
    200; shed queries are NOT counted as tenant failures (the burn can
    recover)."""
    host = make_host([make_tenant_spec("noisy", seed=1, slo=SLO),
                      make_tenant_spec("quiet", seed=2, slo=SLO)],
                     admission=True, retry_after_s=2.0)
    noisy = host.tenants["noisy"]
    assert noisy.slo is not None
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        t0 = time.monotonic()
        noisy.slo.tick(now=t0)
        # burn: malformed queries answer 400 through the gate
        for _ in range(5):
            r = await c.post("/t/noisy/queries.json",
                             data=b"{not json", headers={
                                 "Content-Type": "application/json"})
            assert r.status == 400
        noisy.slo.tick(now=t0 + 31)
        assert noisy.slo.breached(exclude_kinds=("freshness",))

        r = await query(c, "noisy")
        assert r.status == 429
        assert r.headers["Retry-After"] == "2"
        assert host._rejected.value(tenant="noisy") == 1
        # shed queries are not failures — else the burn never recovers
        assert host._failures.value(tenant="noisy") == 5

        # the co-hosted quiet tenant is untouched
        assert await scores(c, "quiet")
        assert host._rejected.value(tenant="quiet") == 0

        # admission off: the same burning tenant serves again
        host.config.admission = False
        assert await scores(c, "noisy")
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# tenant label cardinality (the max_series overflow guard)
# ---------------------------------------------------------------------------

async def test_tenant_label_explosion_collapses_to_other(mt_store):
    """The `tenant` label rides the registry's max_series guard: the
    host wires PIO_MT_MAX_TENANT_SERIES onto every tenant-labelled
    metric, an explosion collapses NEW tenants into `other`, and the
    established tenants' series survive intact."""
    host = make_host([make_tenant_spec("alpha", seed=1),
                      make_tenant_spec("beta", seed=2)],
                     max_tenant_series=2)
    assert host._queries.max_series == 2
    assert host._hist.max_series == 2
    c = TestClient(TestServer(host.app))
    await c.start_server()
    try:
        await scores(c, "alpha")
        await scores(c, "beta")
        assert host._queries.value(tenant="alpha") == 1
        assert host._queries.value(tenant="beta") == 1

        # explosion: a flood of novel tenant values (what a bad rollout
        # of machine-generated tenant names would do to the registry)
        for i in range(40):
            host._queries.inc(tenant=f"exploded-{i}")
        assert host._queries.value(tenant="other") == 40
        assert host._queries.series_count() == 3   # alpha, beta, other
        # established tenants' series survive the flood
        await scores(c, "alpha")
        assert host._queries.value(tenant="alpha") == 2
        assert host._queries.value(tenant="beta") == 1
        # and the overflow is observable, per metric
        overflow = host.registry.get("pio_obs_label_overflow_total")
        assert overflow.value(metric="pio_tenant_queries_total") == 40
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# config precedence
# ---------------------------------------------------------------------------

def test_multitenant_config_precedence(monkeypatch):
    cfg = MultiTenantConfig.from_env({"budgetBytes": 1024,
                                      "reloadWaitS": 3.0,
                                      "admission": False})
    assert cfg.budget_bytes == 1024 and cfg.reload_wait_s == 3.0
    assert not cfg.admission
    # env beats the file section; malformed env logged + ignored
    monkeypatch.setenv("PIO_MT_DEVICE_BUDGET_BYTES", "2048")
    monkeypatch.setenv("PIO_MT_SWEEP_INTERVAL_S", "junk")
    monkeypatch.setenv("PIO_MT_MIN_RESIDENT", "3")
    cfg = MultiTenantConfig.from_env({"budgetBytes": 1024,
                                      "sweepIntervalS": 7.0})
    assert cfg.budget_bytes == 2048
    assert cfg.sweep_interval_s == 7.0
    assert cfg.min_resident == 3
    monkeypatch.setenv("PIO_MT_MAX_TENANT_SERIES", "0")
    assert MultiTenantConfig.from_env().max_tenant_series == 1  # clamped
