"""Model kernels: cooccurrence, NaiveBayes (both variants), LogReg,
MarkovChain, BinaryVectorizer (mirrors reference e2 test coverage)."""

import numpy as np
import pytest

from predictionio_tpu.models.cooccurrence import (
    CooccurrenceModel, cooccurrence_topn_host, distinct_pairs,
    train_cooccurrence,
)
from predictionio_tpu.models.logreg import LogRegParams, train_logreg
from predictionio_tpu.models.markov_chain import train_markov_chain
from predictionio_tpu.models.naive_bayes import (
    LabeledPoint, train_categorical_nb, train_multinomial_nb,
)
from predictionio_tpu.models.vectorizer import BinaryVectorizer, split_data


# -- cooccurrence ------------------------------------------------------------

def test_distinct_pairs():
    u = np.array([0, 0, 1, 0], np.int32)
    i = np.array([1, 1, 1, 2], np.int32)
    du, di = distinct_pairs(u, i)
    assert len(du) == 3  # (0,1) deduped


def test_cooccurrence_counts():
    # users 0,1 both saw items {0,1}; user 2 saw {1,2}
    u = np.array([0, 0, 1, 1, 2, 2], np.int32)
    i = np.array([0, 1, 0, 1, 1, 2], np.int32)
    top = train_cooccurrence(u, i, n_users=3, n_items=3, n=5)
    assert dict(top[0]) == {1: 2}
    assert dict(top[1]) == {0: 2, 2: 1}
    assert top[1][0] == (0, 2)  # sorted by count desc
    assert dict(top[2]) == {1: 1}


def test_cooccurrence_dense_matches_host():
    rng = np.random.default_rng(0)
    u = rng.integers(0, 20, 200).astype(np.int32)
    i = rng.integers(0, 15, 200).astype(np.int32)
    dense = train_cooccurrence(u, i, 20, 15, n=5)
    du, di = distinct_pairs(u, i)
    host = cooccurrence_topn_host(du, di, 15, n=5)
    for item in range(15):
        d = dict(dense.get(item, []))
        h = dict(host.get(item, []))
        # top-5 sets may break count ties differently; the count multiset
        # must agree, and shared candidates must have identical counts
        assert sorted(d.values()) == sorted(h.values())
        for cand in set(d) & set(h):
            assert d[cand] == h[cand]


def test_cooccurrence_model_similar():
    model = CooccurrenceModel(
        item_vocab=np.array(["a", "b", "c"], dtype=object),
        top_cooccurrences={0: [(1, 5), (2, 2)], 1: [(0, 5)], 2: [(0, 2)]})
    out = model.similar(["a"], num=2)
    assert out == [("b", 5.0), ("c", 2.0)]
    # query item excluded; black list respected
    out = model.similar(["a", "b"], num=3)
    assert all(i not in ("a", "b") for i, _ in out)
    out = model.similar(["a"], num=2, black_list=["b"])
    assert out == [("c", 2.0)]
    out = model.similar(["a"], num=2, white_list=["b"])
    assert out == [("b", 5.0)]
    assert model.similar(["zzz"], num=2) == []


# -- categorical NB (e2 parity fixture) --------------------------------------

@pytest.fixture
def nb_points():
    # e2 NaiveBayesFixture-style: label from first feature mostly
    return [
        LabeledPoint("spam", ("free", "money", "now")),
        LabeledPoint("spam", ("free", "cash", "now")),
        LabeledPoint("ham", ("meeting", "money", "tomorrow")),
        LabeledPoint("ham", ("meeting", "agenda", "tomorrow")),
    ]


def test_categorical_nb_train_structure(nb_points):
    model = train_categorical_nb(nb_points)
    assert set(model.priors) == {"spam", "ham"}
    assert model.priors["spam"] == pytest.approx(np.log(0.5))
    # position 0 'free' appears in 2/2 spam
    assert model.likelihoods["spam"][0]["free"] == pytest.approx(0.0)
    assert "free" not in model.likelihoods["ham"][0]


def test_categorical_nb_predict(nb_points):
    model = train_categorical_nb(nb_points)
    assert model.predict(("free", "money", "now")) == "spam"
    assert model.predict(("meeting", "agenda", "tomorrow")) == "ham"


def test_categorical_nb_log_score(nb_points):
    model = train_categorical_nb(nb_points)
    s = model.log_score(LabeledPoint("spam", ("free", "money", "now")))
    assert s == pytest.approx(np.log(0.5) + 0.0 + np.log(0.5) + 0.0)
    # unknown label -> None
    assert model.log_score(LabeledPoint("eggs", ("free",))) is None
    # unseen feature -> -inf by default, custom default applies
    assert model.log_score(
        LabeledPoint("spam", ("UNSEEN", "money", "now"))) == float("-inf")
    s = model.log_score(LabeledPoint("spam", ("UNSEEN", "money", "now")),
                        default_likelihood=lambda ls: min(ls) - 1)
    assert np.isfinite(s)


# -- multinomial NB / logreg -------------------------------------------------

def classification_data(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.poisson(2.0, size=(n, 3)).astype(np.float32)
    labels = np.where(X[:, 0] > X[:, 1], "1.0", "0.0")
    return X, [str(l) for l in labels]


def test_multinomial_nb_learns():
    X, y = classification_data()
    model = train_multinomial_nb(X, y)
    pred = model.predict(X)
    acc = (pred == np.asarray(y, dtype=object)).mean()
    assert acc > 0.75
    assert set(model.label_vocab) == {"0.0", "1.0"}


def test_logreg_learns():
    X, y = classification_data()
    model = train_logreg(X, y, LogRegParams(iterations=300))
    acc = (model.predict(X) == np.asarray(y, dtype=object)).mean()
    assert acc > 0.9


# -- markov chain ------------------------------------------------------------

def test_markov_chain():
    src = np.array([0, 0, 0, 1, 1, 2])
    dst = np.array([1, 1, 2, 0, 2, 0])
    cnt = np.ones(6)
    model = train_markov_chain(src, dst, cnt, n_states=3, top_n=2)
    # row 0: 1 with 2/3, 2 with 1/3
    assert model.predict(0)[0] == (1, pytest.approx(2 / 3))
    assert model.predict(0)[1] == (2, pytest.approx(1 / 3))
    assert model.predict(1)[0][1] == pytest.approx(0.5)
    assert model.predict(2) == [(0, 1.0)]
    # top_n truncates
    m1 = train_markov_chain(src, dst, cnt, n_states=3, top_n=1)
    assert len(m1.predict(0)) == 1


# -- vectorizer / split ------------------------------------------------------

def test_binary_vectorizer():
    rows = [{"color": "red", "size": "L"}, {"color": "blue", "size": "L"}]
    vec = BinaryVectorizer.fit(rows, ["color", "size"])
    assert vec.num_features == 3  # red, blue, L
    v = vec.to_vector({"color": "red", "size": "L"})
    assert v.sum() == 2.0
    m = vec.to_matrix(rows)
    assert m.shape == (2, 3)
    assert (m.sum(axis=1) == 2).all()
    # unseen value ignored
    assert vec.to_vector({"color": "green"}).sum() == 0.0


def test_split_data():
    folds = list(split_data(3, 10))
    assert len(folds) == 3
    for train, test in folds:
        assert len(train) + len(test) == 10
        assert not set(train) & set(test)
    all_test = np.concatenate([t for _, t in folds])
    assert sorted(all_test.tolist()) == list(range(10))


# -- mesh-sharded training equivalence (SURVEY §2.9 P1) ----------------------
# single-device vs 8-virtual-device results must agree: the shard layout
# (row blocks, psum'd counts, tree subsets) is a performance choice, not a
# semantic one.

def _mesh1():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]), axis_names=("data",))


def test_cooccurrence_sharded_matches_single(mesh8):
    from predictionio_tpu.models.cooccurrence import cooccurrence_topn

    rng = np.random.default_rng(1)
    u = rng.integers(0, 50, 600).astype(np.int32)
    i = rng.integers(0, 37, 600).astype(np.int32)
    du, di = distinct_pairs(u, i)
    v1, i1 = cooccurrence_topn(_mesh1(), du, di, 50, 37, 5)
    v8, i8 = cooccurrence_topn(mesh8, du, di, 50, 37, 5)
    np.testing.assert_array_equal(v1, v8)
    # idx may tie-break differently across blockings/backends where counts
    # tie (including ties with items just OUTSIDE the top-k); positions
    # strictly above the row's k-th count are tie-free and must match
    checked = 0
    for r in range(37):
        inside = v1[r] > v1[r][-1]
        # ties WITHIN the top also order freely: compare as sets
        assert set(i1[r][inside].tolist()) == set(i8[r][inside].tolist())
        checked += int(inside.sum())
    assert checked


def test_multinomial_nb_sharded_gate_organic(mesh8):
    # crosses DEVICE_MIN_SIZE (1M elements) WITHOUT monkey-patching: the
    # sharded count path must engage on its own at realistic corpus sizes
    # (r4 verdict weak #5 — the gate value itself was never validated)
    from predictionio_tpu.models import naive_bayes
    from predictionio_tpu.ops import device_cache

    rng = np.random.default_rng(12)
    n_docs = 140_000                       # x 8 features = 1.12M elements
    X = rng.poisson(1.0, size=(n_docs, 8)).astype(np.float32)
    assert X.size >= naive_bayes.DEVICE_MIN_SIZE
    y = np.where(rng.random(n_docs) < 0.5, "a", "b")
    m1 = train_multinomial_nb(X, y)
    between = device_cache.size()
    m8 = train_multinomial_nb(X, y, mesh=mesh8)
    # the SHARDED path committed X to the mesh via the resident cache
    # (the single-device m1 train populates its own entry first — only
    # the m1->m8 delta proves the sharded branch engaged)
    assert device_cache.size() > between
    np.testing.assert_allclose(m1.log_prob, m8.log_prob, atol=1e-5)
    np.testing.assert_allclose(m1.log_prior, m8.log_prior, atol=1e-6)


def test_device_cache_identity_and_eviction():
    from predictionio_tpu.ops import device_cache

    built = []
    a = np.arange(8, dtype=np.float32)

    def build():
        built.append(1)
        return "payload"

    assert device_cache.resident([a], ("t",), build) == "payload"
    assert device_cache.resident([a], ("t",), build) == "payload"
    assert len(built) == 1                 # second call hit the cache
    assert device_cache.resident([a], ("other",), build) == "payload"
    assert len(built) == 2                 # different layout key rebuilds
    n = device_cache.size()
    del a                                   # GC evicts both entries
    import gc

    gc.collect()
    assert device_cache.size() == n - 2


def test_multinomial_nb_sharded_matches_single(mesh8, monkeypatch):
    from predictionio_tpu.models import naive_bayes

    # force the sharded device path even at test size (the size gate
    # would otherwise route this to the host counter)
    monkeypatch.setattr(naive_bayes, "DEVICE_MIN_SIZE", 0)
    rng = np.random.default_rng(2)
    X = rng.poisson(1.0, size=(203, 17)).astype(np.float32)
    y = np.where(rng.random(203) < 0.5, "a", "b")
    m1 = train_multinomial_nb(X, y)
    m8 = train_multinomial_nb(X, y, mesh=mesh8)
    np.testing.assert_allclose(m1.log_prob, m8.log_prob, atol=1e-5)
    np.testing.assert_allclose(m1.log_prior, m8.log_prior, atol=1e-6)
    np.testing.assert_array_equal(m1.predict(X), m8.predict(X))


def test_logreg_sharded_matches_single(mesh8):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(117, 5)).astype(np.float32)
    w_true = rng.normal(size=(5,))
    y = np.where(X @ w_true > 0, "pos", "neg")
    p = LogRegParams(iterations=60, learning_rate=0.2, seed=0)
    m1 = train_logreg(X, y, p)
    m8 = train_logreg(X, y, p, mesh=mesh8)
    # same optimization trajectory up to f32 reduction-order noise
    np.testing.assert_allclose(m1.W, m8.W, atol=2e-3)
    acc8 = (m8.predict(X) == y).mean()
    assert acc8 > 0.9


def test_forest_sharded_matches_single(mesh8):
    from predictionio_tpu.models.forest import ForestParams, train_forest

    rng = np.random.default_rng(4)
    X = rng.normal(size=(150, 4)).astype(np.float32)
    y = np.where(X[:, 0] + X[:, 1] > 0, "hi", "lo")
    p = ForestParams(num_trees=8, max_depth=3, max_bins=16, seed=5)
    m1 = train_forest(X, y, p)
    m8 = train_forest(X, y, p, mesh=mesh8)
    # identical RNG draws + per-tree independence: same trees, same model
    np.testing.assert_array_equal(m1.feat, m8.feat)
    np.testing.assert_array_equal(m1.thr, m8.thr)
    np.testing.assert_array_equal(m1.leaf, m8.leaf)
    assert (m8.predict(X) == y).mean() > 0.85


def _mesh42():
    """Multi-axis mesh: shard_map paths shard over axis 0 (size 4) only;
    regression rig for the total-vs-first-axis device-count confusion."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                axis_names=("data", "model"))


def test_cooccurrence_multi_axis_mesh_matches_single(mesh8):
    # engines pass mesh_of(ctx) verbatim; a runtime_conf mesh_shape "4,2"
    # must produce the same model as a single device (r4 advisor: block
    # geometry keyed off total device count crashed train on such meshes)
    del mesh8  # only to ensure 8 virtual devices exist
    from predictionio_tpu.models.cooccurrence import cooccurrence_topn

    rng = np.random.default_rng(6)
    u = rng.integers(0, 50, 600).astype(np.int32)
    i = rng.integers(0, 37, 600).astype(np.int32)
    du, di = distinct_pairs(u, i)
    v1, _ = cooccurrence_topn(_mesh1(), du, di, 50, 37, 5)
    v42, _ = cooccurrence_topn(_mesh42(), du, di, 50, 37, 5)
    np.testing.assert_array_equal(v1, v42)


def test_cooccurrence_multi_slab_matches_reference(mesh8):
    # item space large enough that each device's column block spans
    # SEVERAL 512-row slabs (the O(ni^2)-free kernel path, r5): results
    # must equal the dense numpy counts
    import jax
    from jax.sharding import Mesh

    from predictionio_tpu.models.cooccurrence import cooccurrence_topn

    mesh2 = Mesh(np.asarray(jax.devices()[:2]), axis_names=("data",))
    rng = np.random.default_rng(8)
    nu, ni = 180, 1400              # blk = 768 -> 2 slabs per device
    u = rng.integers(0, nu, 6000).astype(np.int32)
    i = rng.integers(0, ni, 6000).astype(np.int32)
    du, di = distinct_pairs(u, i)
    vals, idx = cooccurrence_topn(mesh2, du, di, nu, ni, 5)
    a = np.zeros((nu, ni), np.float32)
    a[du, di] = 1.0
    c = a.T @ a
    np.fill_diagonal(c, 0.0)
    ref = -np.sort(-c, axis=1)[:, :5]
    np.testing.assert_array_equal(vals, ref.astype(vals.dtype))


def test_forest_padded_trees_sliced_off(mesh8):
    # num_trees not a multiple of the shard count: the fit pads, but the
    # MODEL must keep exactly num_trees and match the single-device run
    # on every mesh shape (r4 advisor finding)
    from predictionio_tpu.models.forest import ForestParams, train_forest

    rng = np.random.default_rng(7)
    X = rng.normal(size=(120, 4)).astype(np.float32)
    y = np.where(X[:, 0] - X[:, 2] > 0, "hi", "lo")
    p = ForestParams(num_trees=6, max_depth=3, max_bins=16, seed=9)
    m1 = train_forest(X, y, p)
    for mesh in (mesh8, _mesh42()):
        mm = train_forest(X, y, p, mesh=mesh)
        assert mm.feat.shape[0] == 6
        np.testing.assert_array_equal(m1.feat, mm.feat)
        np.testing.assert_array_equal(m1.thr, mm.thr)
        np.testing.assert_array_equal(m1.leaf, mm.leaf)
