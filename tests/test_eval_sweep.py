"""Device-batched evaluation sweep: parity, compile ledger, failure paths.

The tentpole contract under test:
  * the batched (vmapped) sweep matches the sequential per-candidate
    execution of the SAME kernels to 1e-5 per candidate, and the
    engine-level vectorized evaluator picks the same best EngineParams
    as the pre-existing DASE sequential loop;
  * the XLA compile ledger of a sweep equals the number of distinct
    ranks, not the grid size;
  * fold splitting is vectorized and rejects k > n;
  * a failing evaluation persists EVALFAILED (not a stuck INIT) and the
    per-candidate wall-time/compile-group breakdown lands in
    evaluator_results_json.
"""

import json
import os

import numpy as np
import pytest

from predictionio_tpu.core import Engine, EngineParams, MetricEvaluator
from predictionio_tpu.core.cross_validation import (
    fold_assignments, fold_masks, k_fold, split_data,
)
from predictionio_tpu.core.evaluation import Evaluation, expand_param_grid
from predictionio_tpu.engines.recommendation import (
    ALSAlgorithm, AlgorithmParams, DataSourceParams, PrecisionAtK,
    RatingColumns, RecommendationDataSource, RecommendationPreparator,
    RecommendationServing, RMSEMetric,
)
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.models.als_sweep import build_sweep_data, run_sweep


class Ctx:
    pass


# ---------------------------------------------------------------------------
# split_data vectorization + validation
# ---------------------------------------------------------------------------

def test_split_data_rejects_k_above_n():
    with pytest.raises(ValueError, match="exceeds"):
        list(split_data(5, 3))
    with pytest.raises(ValueError, match="exceeds"):
        list(k_fold([1, 2], 3))
    with pytest.raises(ValueError):
        fold_assignments(4, 2)


def test_split_data_still_rejects_k_below_one():
    with pytest.raises(ValueError, match=">= 1"):
        list(split_data(0, 10))


def test_fold_masks_match_split_data():
    k, n = 4, 21
    masks = fold_masks(k, n)
    assert masks.shape == (k, n)
    # every point is in exactly one test fold
    assert (masks.sum(axis=0) == 1).all()
    for fold, (train, test) in enumerate(split_data(k, n)):
        assert np.array_equal(np.flatnonzero(masks[fold]), test)
        assert np.array_equal(np.flatnonzero(~masks[fold]), train)


def test_fold_assignments_is_index_mod_k():
    assert np.array_equal(fold_assignments(3, 7),
                          np.asarray([0, 1, 2, 0, 1, 2, 0]))


# ---------------------------------------------------------------------------
# Kernel-level parity: batched vmap vs sequential execution
# ---------------------------------------------------------------------------

def _synthetic(nu, ni, nnz, seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, nu, nnz).astype(np.int32)
    items = rng.integers(0, ni, nnz).astype(np.int32)
    lu, lv = rng.normal(size=(nu, 3)), rng.normal(size=(ni, 3))
    ratings = np.clip(np.round(
        2.5 + np.einsum("nk,nk->n", lu[users], lv[items])), 1, 5
    ).astype(np.float32)
    return users, items, ratings


def test_batched_sweep_matches_sequential_kernel_to_1e5():
    nu, ni, nnz, k = 50, 30, 1500, 3
    users, items, ratings = _synthetic(nu, ni, nnz, seed=1)
    fold_of = fold_assignments(k, nnz)
    data = build_sweep_data(users, items, ratings, fold_of, nu, ni)
    cands = [ALSParams(rank=r, num_iterations=3, reg=g, chunk_size=2048)
             for r in (3, 5) for g in (0.02, 0.2)]
    batched = run_sweep(data, cands, rank_metrics=(5, 4, 2.0))
    sequential = run_sweep(data, cands, rank_metrics=(5, 4, 2.0),
                           batched=False)
    assert batched.mode == "batched" and sequential.mode == "sequential"
    assert batched.n_groups == 2        # two distinct ranks
    denom = min(4, min(5, ni))
    for cb, cs in zip(batched.candidates, sequential.candidates):
        # the continuous metric matches to 1e-5; the rank-QUANTIZED
        # metrics may flip a single near-tied top-k edge (vmap reorders
        # float reductions at ~1e-7, and a tie within that noise moves a
        # whole 1/denom precision point), so they are asserted as
        # at-most-one-flipped-hit instead
        assert cb.heldout_rmse == pytest.approx(cs.heldout_rmse, abs=1e-5)
        hits_b = round(cb.precision * denom * cb.n_qual)
        hits_s = round(cs.precision * denom * cs.n_qual)
        assert abs(hits_b - hits_s) <= max(1, cb.n_qual // 100), \
            (hits_b, hits_s, cb.n_qual)
        assert cb.topn_mse == pytest.approx(cs.topn_mse, abs=0.05)
        assert cb.n_test == cs.n_test and cb.n_qual == cs.n_qual
    # best candidate identical
    best_b = min(range(len(cands)),
                 key=lambda i: batched.candidates[i].heldout_rmse)
    best_s = min(range(len(cands)),
                 key=lambda i: sequential.candidates[i].heldout_rmse)
    assert best_b == best_s


def test_sweep_pools_folds_and_attributes_cost():
    nu, ni, nnz, k = 31, 17, 800, 2
    users, items, ratings = _synthetic(nu, ni, nnz, seed=2)
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(k, nnz), nu, ni)
    cands = [ALSParams(rank=4, num_iterations=2, reg=g) for g in (0.1, 0.3)]
    res = run_sweep(data, cands)
    assert len(res.candidates) == 2
    for c in res.candidates:
        assert np.isfinite(c.heldout_rmse)
        # pooled over BOTH folds: every rating is a test point exactly once
        assert c.n_test == nnz
        assert c.wall_s > 0
        assert c.group.endswith("rank=4")
    assert res.batch_sizes == [4]       # 2 candidates x 2 folds, one launch


def test_warm_start_runs_and_converges_no_worse():
    nu, ni, nnz, k = 40, 22, 1200, 2
    users, items, ratings = _synthetic(nu, ni, nnz, seed=3)
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(k, nnz), nu, ni)
    cands = [ALSParams(rank=r, num_iterations=4, reg=0.1) for r in (3, 5)]
    cold = run_sweep(data, cands)
    warm = run_sweep(data, cands, warm_start=True)
    for cc, cw in zip(cold.candidates, warm.candidates):
        assert np.isfinite(cw.heldout_rmse)
        # warm start is an accuracy knob, not a parity mode: just bound
        # it against catastrophics
        assert cw.heldout_rmse < cc.heldout_rmse * 1.5 + 1.0


def test_cold_users_are_misses_not_free_hits():
    """A user whose EVERY rating lands in the test fold trains to an
    exactly-zero factor row; an all-zero score row would rank its
    held-out item 0 (a guaranteed 'hit'). The sequential path serves
    unknown users an empty list — a miss — so the device kernel must
    mask cold users out of the hit count."""
    # 10 users, ONE rating each, k=2: every test entry's user is cold in
    # its own fold, so precision must be exactly 0, never ~1
    n = 10
    users = np.arange(n, dtype=np.int32)
    items = (np.arange(n, dtype=np.int32) % 4)
    ratings = np.full(n, 5.0, np.float32)        # all qualify
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(2, n), n, 4)
    res = run_sweep(data, [ALSParams(rank=2, num_iterations=2, reg=0.1)],
                    rank_metrics=(3, 3, 2.0))
    c = res.candidates[0]
    assert c.n_qual == n
    assert c.precision == 0.0


def test_sweep_kind_not_inherited_past_custom_math():
    """A metric subclass that customizes calculate_point without
    re-declaring sweep_kind must NOT silently get the stock device
    kernel — the evaluator falls back to its (customized) sequential
    math."""
    from predictionio_tpu.core.evaluation import sweep_kind_of

    class InheritedPrecision(PrecisionAtK):       # custom math, no kind
        def calculate_point(self, eval_info, q, p, a):
            return 1.0

    class RedeclaredPrecision(InheritedPrecision):  # explicit opt back in
        sweep_kind = "precision_at_k"

    assert sweep_kind_of(PrecisionAtK()) == "precision_at_k"
    assert sweep_kind_of(InheritedPrecision()) is None
    assert sweep_kind_of(RedeclaredPrecision()) == "precision_at_k"

    engine = _mem_engine(seed=19)
    result = MetricEvaluator(InheritedPrecision(k=3), output_path=None) \
        .evaluate(Ctx(), engine, _grid_eps(ranks=(3,), regs=(0.1,)))
    assert result.sweep["mode"] == "sequential"
    assert result.best_score == 1.0               # the override ran


def test_subspace_sweep_batched_matches_sequential():
    """`pio eval` grids inherit the subspace training kernel: candidates
    carrying solver="subspace" ride the vmapped sweep, and the batched
    execution matches the sequential execution of the SAME kernels —
    including the best-candidate pick."""
    nu, ni, nnz, k = 48, 28, 1400, 3
    users, items, ratings = _synthetic(nu, ni, nnz, seed=6)
    fold_of = fold_assignments(k, nnz)
    data = build_sweep_data(users, items, ratings, fold_of, nu, ni)
    cands = [ALSParams(rank=r, num_iterations=3, reg=g, chunk_size=2048,
                       solver="subspace", block_size=2)
             for r in (4, 6) for g in (0.02, 0.2)]
    batched = run_sweep(data, cands)
    sequential = run_sweep(data, cands, batched=False)
    assert batched.n_groups == 2        # two (rank, block_size) families
    for cb, cs in zip(batched.candidates, sequential.candidates):
        assert cb.heldout_rmse == pytest.approx(cs.heldout_rmse, abs=1e-5)
        assert cb.group.endswith("/sub2")
    best_b = min(range(len(cands)),
                 key=lambda i: batched.candidates[i].heldout_rmse)
    best_s = min(range(len(cands)),
                 key=lambda i: sequential.candidates[i].heldout_rmse)
    assert best_b == best_s


def test_sweep_groups_split_by_solver_family():
    """Compile groups are (rank, solver, block_size) families: full
    candidates group together regardless of the block_size they happen
    to carry; each distinct subspace block_size is its own program."""
    nu, ni, nnz, kf = 21, 11, 400, 2
    users, items, ratings = _synthetic(nu, ni, nnz, seed=7)
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(kf, nnz), nu, ni)
    cands = [
        ALSParams(rank=4, num_iterations=2, reg=0.1),
        ALSParams(rank=4, num_iterations=2, reg=0.2, block_size=9),
        ALSParams(rank=4, num_iterations=2, reg=0.1,
                  solver="subspace", block_size=2),
        ALSParams(rank=4, num_iterations=2, reg=0.1,
                  solver="subspace", block_size=3),
    ]
    res = run_sweep(data, cands)
    assert res.n_groups == 3
    groups = [c.group for c in res.candidates]
    assert groups[0] == groups[1]               # full: block_size inert
    assert groups[2].endswith("/sub2")
    assert groups[3].endswith("/sub3")
    with pytest.raises(ValueError, match="unknown ALS solver"):
        run_sweep(data, [ALSParams(rank=4, solver="nope")])


def test_mixed_iterations_share_a_compile_group():
    """num_iterations is shape-preserving: candidates differing only in
    iteration count ride ONE compile group (traced per-unit trip count),
    and fewer iterations means a genuinely different result."""
    nu, ni, nnz, k = 23, 13, 500, 2
    users, items, ratings = _synthetic(nu, ni, nnz, seed=4)
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(k, nnz), nu, ni)
    cands = [ALSParams(rank=4, num_iterations=it, reg=0.1) for it in (1, 4)]
    res = run_sweep(data, cands)
    assert res.n_groups == 1
    assert res.candidates[0].heldout_rmse != pytest.approx(
        res.candidates[1].heldout_rmse, abs=1e-9)


# ---------------------------------------------------------------------------
# Compile ledger: pio_jax_compile_total delta == distinct ranks
# ---------------------------------------------------------------------------

def _compile_total(family):
    from predictionio_tpu.obs.jax_stats import compile_counter

    for labels, value in compile_counter().samples():
        if labels.get("family") == family:
            return value
    return 0.0


def test_compile_ledger_counts_ranks_not_grid_size():
    # unique data dims so this test's cache keys cannot collide with
    # other tests' (fn_cache dedups sightings per key)
    nu, ni, nnz, k = 37, 19, 700, 2
    users, items, ratings = _synthetic(nu, ni, nnz, seed=5)
    data = build_sweep_data(users, items, ratings,
                            fold_assignments(k, nnz), nu, ni)
    # 8 candidates, only TWO distinct ranks
    cands = [ALSParams(rank=r, num_iterations=2, reg=g, seed=s)
             for r in (3, 4) for g in (0.05, 0.5) for s in (1, 2)]
    before = _compile_total("als_eval_sweep")
    res = run_sweep(data, cands)
    delta = _compile_total("als_eval_sweep") - before
    assert delta == 2 == res.n_groups, (
        f"compile ledger grew by {delta} for 2 distinct ranks "
        f"({len(cands)} candidates)")
    # re-running the identical sweep compiles NOTHING new
    run_sweep(data, cands)
    assert _compile_total("als_eval_sweep") - before == 2


# ---------------------------------------------------------------------------
# Engine-level: vectorized evaluator vs the DASE sequential loop
# ---------------------------------------------------------------------------

def _mem_engine(nu=40, ni=24, per_user=10, seed=7):
    """Recommendation engine over an in-memory rating set (no storage)."""
    rng = np.random.default_rng(seed)
    rows = []
    for u in range(nu):
        for i in rng.choice(ni, size=per_user, replace=False):
            rows.append((f"u{u:03d}", f"i{i:03d}",
                         float(rng.integers(1, 6))))
    users = np.asarray([r[0] for r in rows], dtype=object)
    items = np.asarray([r[1] for r in rows], dtype=object)
    vals = np.asarray([r[2] for r in rows], dtype=np.float32)

    class MemDS(RecommendationDataSource):
        def _read_columns(self):
            return RatingColumns(users=users, items=items, values=vals)

    return Engine(MemDS, RecommendationPreparator, {"als": ALSAlgorithm},
                  RecommendationServing)


def _grid_eps(ranks=(3, 5), regs=(0.05, 0.3), k_fold=2, query_num=4,
              iters=2):
    return [EngineParams(
        data_source_params=DataSourceParams(
            app_name="mem",
            eval_params={"kFold": k_fold, "queryNum": query_num}),
        algorithm_params_list=[("als", AlgorithmParams(
            rank=r, num_iterations=iters, reg=g))])
        for r in ranks for g in regs]


def test_evaluator_vectorized_selects_same_best(monkeypatch):
    engine = _mem_engine()
    eps = _grid_eps()
    evaluator = MetricEvaluator(PrecisionAtK(k=3), output_path=None)
    batched = evaluator.evaluate(Ctx(), engine, eps)
    monkeypatch.setenv("PIO_EVAL_VECTORIZE", "0")
    sequential = evaluator.evaluate(Ctx(), engine, eps)
    assert batched.sweep["mode"] == "batched"
    assert sequential.sweep["mode"] == "sequential"
    assert batched.sweep["compileGroups"] == 2
    # same winner; scores agree to tie-flip tolerance (the sequential
    # DASE path trains on per-fold subset BUILDS, the batched path on a
    # fold-masked shared layout — identical math, different float
    # summation boundaries, so near-tied top-k edges can flip a handful
    # of quantized precision points)
    assert batched.best_idx == sequential.best_idx
    for (_, sb, _o1), (_, ss, _o2) in zip(
            batched.engine_params_scores,
            sequential.engine_params_scores):
        assert sb == pytest.approx(ss, abs=5e-3)
    # per-candidate breakdown present on both paths
    assert len(batched.candidate_details) == len(eps)
    assert batched.candidate_details[0]["group"].startswith("g")
    assert sequential.candidate_details[0]["group"] == "sequential"
    assert all(d["wallTimeS"] >= 0 for d in batched.candidate_details)
    js = json.loads(json.dumps(batched.to_json_dict()))
    assert js["sweep"]["mode"] == "batched"
    assert len(js["candidates"]) == len(eps)


def test_evaluator_vectorized_other_metrics_device_computed():
    engine = _mem_engine(seed=11)
    eps = _grid_eps(ranks=(3,), regs=(0.05, 0.5))
    evaluator = MetricEvaluator(PrecisionAtK(k=3),
                                other_metrics=[RMSEMetric()],
                                output_path=None)
    result = evaluator.evaluate(Ctx(), engine, eps)
    assert result.sweep["mode"] == "batched"
    for _ep, _score, others in result.engine_params_scores:
        assert len(others) == 1 and np.isfinite(others[0])


def test_evaluator_falls_back_without_sweep_support():
    """Metrics without a sweep_kind keep the sequential loop."""
    class HostOnlyPrecision(PrecisionAtK):
        sweep_kind = None

    engine = _mem_engine(seed=13)
    eps = _grid_eps(ranks=(3,), regs=(0.1,))
    result = MetricEvaluator(HostOnlyPrecision(k=3),
                             output_path=None).evaluate(Ctx(), engine, eps)
    assert result.sweep["mode"] == "sequential"
    assert result.candidate_details[0]["group"] == "sequential"


def test_expand_param_grid_cross_product():
    base = _grid_eps(ranks=(3,), regs=(0.1,))
    out = expand_param_grid(base, ["rank=4,6", "reg=0.01,0.1,0.5"])
    assert len(out) == 6
    combos = {(ep.algorithm_params_list[0][1].rank,
               ep.algorithm_params_list[0][1].reg) for ep in out}
    assert combos == {(4, 0.01), (4, 0.1), (4, 0.5),
                      (6, 0.01), (6, 0.1), (6, 0.5)}
    # shared non-algo params survive
    assert all(ep.data_source_params.eval_params["kFold"] == 2
               for ep in out)
    with pytest.raises(ValueError, match="not a parameter"):
        expand_param_grid(base, ["nope=1,2"])
    with pytest.raises(ValueError, match="expected"):
        expand_param_grid(base, ["rank"])
    with pytest.raises(ValueError, match="twice"):
        expand_param_grid(base, ["rank=8,12", "rank=16,24"])
    assert expand_param_grid(base, []) == base


# ---------------------------------------------------------------------------
# Workflow persistence: EVALFAILED + per-candidate JSON
# ---------------------------------------------------------------------------

@pytest.fixture()
def meta(tmp_path):
    from predictionio_tpu.storage import Storage

    Storage.configure({
        "sources": {"DB": {"TYPE": "sqlite",
                           "PATH": str(tmp_path / "eval.db")}},
        "repositories": {
            "METADATA": {"NAME": "pio", "SOURCE": "DB"},
            "EVENTDATA": {"NAME": "pio", "SOURCE": "DB"},
            "MODELDATA": {"NAME": "pio", "SOURCE": "DB"},
        },
    })
    yield Storage
    Storage.reset()


def test_failed_evaluation_persists_evalfailed(meta):
    from predictionio_tpu.workflow import run_evaluation

    class BoomEvaluation(Evaluation):
        def run(self, ctx, engine_params_list):
            raise RuntimeError("sweep exploded")

    with pytest.raises(RuntimeError, match="sweep exploded"):
        run_evaluation(BoomEvaluation(), _grid_eps(ranks=(3,), regs=(0.1,)),
                       evaluation_class="BoomEvaluation")
    stored = meta.get_meta_data_evaluation_instances().get_all()
    assert len(stored) == 1
    assert stored[0].status == "EVALFAILED"
    assert "RuntimeError: sweep exploded" in stored[0].evaluator_results


def test_evaluation_persists_candidate_breakdown(meta):
    from predictionio_tpu.workflow import run_evaluation

    engine = _mem_engine(seed=17)
    eps = _grid_eps(ranks=(3, 4), regs=(0.1,))
    ev = Evaluation(engine=engine, metric=PrecisionAtK(k=3),
                    output_path=None)
    run_evaluation(ev, eps, evaluation_class="MemEval")
    stored = meta.get_meta_data_evaluation_instances().get_completed()
    assert len(stored) == 1
    js = json.loads(stored[0].evaluator_results_json)
    assert len(js["candidates"]) == len(eps)
    for cand in js["candidates"]:
        assert cand["wallTimeS"] >= 0
        assert "group" in cand
    assert js["sweep"]["mode"] == "batched"
    assert js["sweep"]["compileGroups"] == 2
